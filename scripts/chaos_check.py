#!/usr/bin/env python
"""Chaos equivalence harness (resilience/ acceptance gate).

Runs the same bounded check twice on CPU:

1. an UNINTERRUPTED baseline run, and
2. a SUPERVISED run under a deterministic fault plan (default: a torn
   checkpoint write at level 2 and a mid-level kill at level 3),

then asserts the supervised run's ``(distinct, generated, diameter,
levels)`` — read from each run's JSONL ``run_end`` event, the supported
telemetry interface — are BIT-IDENTICAL to the baseline's, and that the
supervised log carries at least one ``restart`` event (i.e. the faults
actually fired and recovery actually ran).  When the plan injects an
``oom`` fault, a ``degraded`` event is required too, and the run must
still complete.  Exit 0 on equivalence, 1 on any mismatch — CI-callable.

    python scripts/chaos_check.py
    python scripts/chaos_check.py --faults 'kill@level=2,oom@chunk=2' \\
        --max-diameter 4

Subprocess-based on purpose: the kill faults die via ``os._exit`` (hard
mode), exactly what a real crash leaves behind; the persistent
compilation cache (enabled by the CLI) keeps the restarts cheap.
"""

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def last_event(path, event):
    """Newest JSONL record of ``event`` in ``path`` (None if absent)."""
    hit = None
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("event") == event:
                hit = rec
    return hit


def count_events(path, event):
    with open(path, encoding="utf-8") as f:
        return sum(1 for line in f if line.strip()
                   and json.loads(line).get("event") == event)


def counters_of(run_end):
    return (run_end["distinct"], run_end["generated"],
            run_end["diameter"], tuple(run_end["levels"]))


def main(argv=None):
    ap = argparse.ArgumentParser(prog="chaos_check")
    ap.add_argument("--cfg", default="configs/MCraft_bounded.cfg")
    ap.add_argument("--faults",
                    default="ckpt_torn_write@level=2,kill@level=3")
    ap.add_argument("--max-diameter", type=int, default=4)
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--restarts", type=int, default=5)
    ap.add_argument("--keep-workdir", action="store_true")
    ap.add_argument("--workdir", default=None,
                    help="run in this directory (implies --keep-workdir; "
                         "CI points it somewhere uploadable so the event "
                         "logs + Chrome traces become artifacts)")
    args = ap.parse_args(argv)

    if args.workdir:
        work = os.path.abspath(args.workdir)
        os.makedirs(work, exist_ok=True)
        args.keep_workdir = True
    else:
        work = tempfile.mkdtemp(prefix="chaos_")
    base = [sys.executable, "-m", "raft_tla_tpu", "check",
            os.path.join(REPO, args.cfg), "--platform", "cpu",
            "--batch", str(args.batch),
            "--queue-capacity", str(1 << 12),
            "--seen-capacity", str(1 << 15),
            "--max-diameter", str(args.max_diameter),
            # Sparse chunk-stage sampling (observational, bit-identical
            # on/off — tested): the killed child's postmortem must
            # carry chunk-stage samples, not just progress.
            "--profile-chunks", "4",
            "--progress-interval", "0"]
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)       # single-device children
    env.pop("FAULT_PLAN", None)      # never leak an ambient plan
    ok = True
    try:
        clean_log = os.path.join(work, "clean.jsonl")
        clean_trace = os.path.join(work, "clean_trace.json")
        print(f"chaos: baseline run ({args.cfg}, "
              f"max_diameter={args.max_diameter})", flush=True)
        # cwd=REPO so `python -m raft_tla_tpu` resolves regardless of
        # where the harness itself was invoked from (no installed pkg).
        rc = subprocess.call(base + ["--events-out", clean_log,
                                     "--trace-out", clean_trace],
                             env=env, cwd=REPO)
        if rc not in (0, 1):
            print(f"FAIL: baseline run exited {rc}")
            return 1

        sup_dir = os.path.join(work, "states")
        sup_log = os.path.join(sup_dir, "events.jsonl")
        sup_env = dict(env, FAULT_PLAN=args.faults,
                       FAULT_STATE_DIR=os.path.join(work, "fault_state"))
        print(f"chaos: supervised run under faults {args.faults!r}",
              flush=True)
        sup_trace = os.path.join(work, "sup_trace.json")
        rc_sup = subprocess.call(
            base + ["--checkpoint-dir", sup_dir,
                    "--checkpoint-interval", "0",
                    "--trace-out", sup_trace,
                    "--supervise", str(args.restarts)],
            env=sup_env, cwd=REPO)
        if rc_sup != rc:
            print(f"FAIL: supervised exit {rc_sup} != baseline {rc}")
            ok = False

        clean_end = last_event(clean_log, "run_end")
        sup_end = last_event(sup_log, "run_end")
        if clean_end is None or sup_end is None:
            print(f"FAIL: missing run_end event "
                  f"(clean={clean_end is not None}, "
                  f"sup={sup_end is not None})")
            return 1
        c, s = counters_of(clean_end), counters_of(sup_end)
        if c != s:
            print(f"FAIL: counters diverge\n  baseline  {c}\n"
                  f"  supervised{s}")
            ok = False
        else:
            print(f"chaos: counters bit-identical: distinct={c[0]} "
                  f"generated={c[1]} diameter={c[2]} levels={list(c[3])}")

        restarts = count_events(sup_log, "restart")
        die_faults = any(f.split("@")[0] in ("kill", "ckpt_torn_write")
                         for f in args.faults.split(","))
        if die_faults and restarts < 1:
            print("FAIL: no 'restart' event — the faults never fired or "
                  "the supervisor never recovered")
            ok = False
        else:
            print(f"chaos: {restarts} restart event(s) in {sup_log}")

        # Flight-recorder gate (obs/flight.py): a hard-killed child must
        # leave its black box behind — postmortem.json next to the
        # checkpoints, holding the last progress snapshot AND
        # chunk-stage samples (ISSUE 9 acceptance), surfaced by the
        # supervisor as a 'postmortem' event.
        if any(f.split("@")[0] == "kill" for f in args.faults.split(",")):
            pm_path = os.path.join(sup_dir, "postmortem.json")
            if not os.path.exists(pm_path):
                print(f"FAIL: injected kill left no postmortem dump at "
                      f"{pm_path}")
                ok = False
            else:
                with open(pm_path, encoding="utf-8") as f:
                    pm = json.load(f)
                recs = pm.get("records") or {}
                prog = recs.get("progress") or []
                stages = recs.get("chunk_stage") or []
                if not pm.get("reason", "").startswith("fault_kill"):
                    print(f"FAIL: postmortem reason {pm.get('reason')!r} "
                          f"is not the injected kill")
                    ok = False
                elif not prog:
                    print("FAIL: postmortem has no progress snapshots")
                    ok = False
                elif not stages:
                    print("FAIL: postmortem has no chunk-stage samples")
                    ok = False
                else:
                    print(f"chaos: postmortem ok ({pm['reason']!r}, "
                          f"{len(prog)} progress snapshot(s), "
                          f"{len(stages)} chunk-stage sample(s), last "
                          f"distinct={prog[-1].get('distinct')})")
            if count_events(sup_log, "postmortem") < 1:
                print("FAIL: supervisor surfaced no 'postmortem' event")
                ok = False

        if any(f.startswith("oom") for f in args.faults.split(",")):
            degraded = count_events(sup_log, "degraded")
            if degraded < 1:
                print("FAIL: oom fault in plan but no 'degraded' event")
                ok = False
            else:
                print(f"chaos: {degraded} degraded event(s)")

        # Trace-format gate: both runs' --trace-out files must be valid
        # Chrome trace JSON arrays (obs.validate_chrome_trace) — the
        # supervised engine trace is the LAST attempt's (each child
        # rewrites it), and the supervisor adds its own attempt/restart
        # timeline next to it.
        sys.path.insert(0, REPO)
        from raft_tla_tpu.obs import validate_chrome_trace
        for tpath in (clean_trace, sup_trace,
                      sup_trace + ".supervisor.json"):
            try:
                n = len(validate_chrome_trace(tpath))
                print(f"chaos: trace ok ({n} events): {tpath}")
            except (OSError, ValueError) as e:
                print(f"FAIL: invalid Chrome trace: {e}")
                ok = False
        print("chaos: OK" if ok else "chaos: FAILED")
        return 0 if ok else 1
    finally:
        if args.keep_workdir:
            print(f"chaos: workdir kept at {work}")
        else:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
