#!/bin/bash
# One-shot TPU measurement session (round-3 performance evidence).
# Run when the TPU tunnel is alive; everything lands in artifacts/.
#
#   bash scripts/tpu_session.sh [budget_seconds_for_northstar]
#
# Stages (each skipped gracefully if a prior one shows the tunnel dead):
#   1. probe           - fail fast if the tunnel is wedged
#   2. profile_step    - per-stage device timings (the round-3 instrument)
#   3. bench           - the driver metric (BENCH_SECONDS=60)
#   4. north star      - raft5/TPUraft.cfg on one chip, checkpoint+spill,
#                        budgeted; level profile recorded
#   5. simulation      - BASELINE configs[3] scale (capped by time budget)
set -u
cd "$(dirname "$0")/.."
mkdir -p artifacts
NS_BUDGET="${1:-900}"

echo "== 1. probe =="
if ! timeout 180 python -c "import jax; d=jax.devices(); assert d[0].platform != 'cpu', d; print('tpu ok:', d)"; then
    echo "TPU tunnel unavailable; aborting session."
    exit 1
fi

echo "== 2. profile_step (B=2048) =="
timeout 1200 python scripts/profile_step.py 2048 2>&1 | grep -v WARNING \
    | tee artifacts/profile_step_tpu.txt

echo "== 3. bench (60 s budget) =="
BENCH_SECONDS=60 timeout 900 python bench.py 2>&1 | grep -v WARNING \
    | tee artifacts/bench_tpu.json

echo "== 4. north-star attempt (budget ${NS_BUDGET}s, ckpt+spill) =="
timeout $((NS_BUDGET + 600)) python -m raft_tla_tpu check \
    configs/TPUraft.cfg --max-seconds "${NS_BUDGET}" --no-trace \
    --checkpoint-dir artifacts/ns_ckpt --spill-dir artifacts/ns_spill \
    2>&1 | grep -v WARNING | tee artifacts/northstar_tpu.txt

echo "== 5. simulation at scale (300 s cap) =="
timeout 600 python -m raft_tla_tpu simulate configs/MCraft_bounded.cfg \
    --batch 8192 --num-steps 134217728 --max-seconds 300 \
    2>&1 | grep -v WARNING | tee artifacts/simulate_tpu.txt

echo "== session complete; artifacts/ =="
ls -la artifacts/
