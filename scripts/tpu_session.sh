#!/bin/bash
# One-shot TPU measurement session.  Run when the TPU tunnel is alive;
# everything lands in artifacts/.
#
#   bash scripts/tpu_session.sh [budget_seconds_for_northstar]
#
# Ordering lesson (2026-07-31, the only tunnel window ever observed): the
# tunnel lived ~5 minutes — long enough for exactly one stage — then
# wedged mid-bench and stayed dead.  So the DRIVER METRIC (bench) runs
# FIRST now, and each stage re-probes and simply skips (not aborts) so a
# transient wedge costs one stage, not the rest of the session.
#
# Stages, in value order:
#   1. probe           - fail fast if the tunnel is wedged
#   2. bench           - the driver metric (BENCH_SECONDS=60)
#   3. leader bench    - leader-rich frontier (log-machinery kernels)
#   4. profile_step    - per-stage device timings
#   5. north star      - raft5/TPUraft.cfg on one chip, checkpoint+spill
#   5b. xla profile    - device-profiler capture (--xla-profile) of the
#                        v2 AND v3 chunks: the NORTHSTAR §d hardware
#                        verdict survives even a cut-short session
#   6. simulation      - BASELINE configs[3] scale (capped)
#
# Live console: the bench and north-star stages serve /metrics +
# /flight on METRICS_PORT (obs/expose.py) and a background
# `python -m raft_tla_tpu watch http://...` writes a live progress log
# into artifacts/ — so a session that dies mid-measurement still shows
# WHERE it was (and the engine's postmortem.json shows the last
# seconds; it lands next to the north-star checkpoints).
set -u
set -o pipefail   # a crashed stage must not be masked by tee
cd "$(dirname "$0")/.."
mkdir -p artifacts
NS_BUDGET="${1:-900}"
METRICS_PORT="${METRICS_PORT:-8790}"

# Background live console against a stage's /flight endpoint; writes to
# the given log.  Dies on its own when the stage's listener goes away.
start_watch() {
    python -m raft_tla_tpu watch "http://127.0.0.1:${METRICS_PORT}" \
        --interval 10 >> "artifacts/$1" 2>&1 &
    WATCH_PID=$!
}
stop_watch() {
    # The watcher exits by itself when the listener disappears; the
    # kill is a backstop so a wedged stage can't leak watchers.
    { kill "$WATCH_PID" 2>/dev/null && wait "$WATCH_PID" 2>/dev/null; } || true
}

probe() {
    # RAFT_SESSION_ALLOW_CPU=1 lets the whole pipeline be smoke-tested
    # without an accelerator (stages then run on the CPU fallback).
    if [ "${RAFT_SESSION_ALLOW_CPU:-0}" = "1" ]; then
        return 0
    fi
    timeout 180 python -c \
        "import jax; assert jax.devices()[0].platform != 'cpu'" \
        2>/dev/null
}

# CLI stages need an explicit platform in CPU-smoke mode — the ambient
# backend is the (possibly dead) tunnel regardless of JAX_PLATFORMS.
PLAT_ARGS=""
if [ "${RAFT_SESSION_ALLOW_CPU:-0}" = "1" ]; then
    PLAT_ARGS="--platform cpu"
fi

echo "== 1. probe =="
if ! probe; then
    echo "TPU tunnel unavailable; aborting session."
    exit 1
fi
echo "tpu ok"

# Single-core host: a background CPU measurement would starve XLA
# compilation for every stage below — the TPU session takes priority the
# moment the tunnel answers.
pkill -f "raft_tla_tpu simulate.*platform cpu" 2>/dev/null && \
    echo "(killed background CPU simulation sweep; TPU session takes priority)"

echo "== 2. bench (60 s budget) =="
# stdout only into the .json — bench prints exactly one JSON line there;
# stderr (progress markers, fallback notices, absl logs) goes to the .log.
# A previously captured result is archived, never truncated by a rerun.
for f in bench_tpu.json leader_bench_tpu.json; do
    [ -s "artifacts/$f" ] && cp "artifacts/$f" "artifacts/$f.$(date +%s).bak"
done
start_watch bench_tpu_watch.log
BENCH_SECONDS=60 BENCH_METRICS_PORT="${METRICS_PORT}" timeout 900 \
    python bench.py \
    2> artifacts/bench_tpu.log | tee artifacts/bench_tpu.json \
    || echo "bench stage failed (rc=$?)"
stop_watch

echo "== 2b. bench at B=8192 (batch-scaling probe, 60 s) =="
if probe; then
    BENCH_SECONDS=60 BENCH_BATCH=8192 BENCH_ORACLE_SECONDS=1 \
        timeout 900 python bench.py \
        2> artifacts/bench_tpu_b8192.log | tee artifacts/bench_tpu_b8192.json \
        || echo "bench b8192 failed (rc=$?)"
else
    echo "skipped: tunnel dead"
fi

echo "== 2c. bench --pipeline v3 (fused Pallas chunk, 60 s) =="
# THE NORTHSTAR §d decision row: the fused Pallas pipeline (Pallas
# compact + fused probe/insert->enqueue tail, real Mosaic lowering on
# TPU) against the v2 XLA chunk measured in stage 2.  bench_diff folds
# the two stage granularities to common stages; the verdict line in the
# log is the §d decision rule resolved by measurement.  A Mosaic
# lowering failure degrades per stage (recorded in fused_stages of the
# JSON), so this stage can never wedge the session on an unlowered kernel.
if probe; then
    BENCH_SECONDS=60 BENCH_PIPELINE=v3 BENCH_ORACLE_SECONDS=1 \
        timeout 900 python bench.py \
        2> artifacts/bench_tpu_v3.log | tee artifacts/bench_tpu_v3.json \
        || echo "bench v3 stage failed (rc=$?)"
    python scripts/bench_diff.py artifacts/bench_tpu.json \
        artifacts/bench_tpu_v3.json \
        | tee artifacts/bench_tpu_v2_vs_v3.txt
    # rc 1 is a measured perf verdict; rc 2 (malformed/missing JSON
    # after a crashed bench) is NOT — never record a crash as the §d
    # decision.  (pipefail makes the pipeline status bench_diff's rc.)
    case $? in
        0) echo "(v3 holds or beats v2 on this hardware)" ;;
        1) echo "(v3 regressed vs v2 on this hardware — see diff above)" ;;
        *) echo "(v2-vs-v3 diff UNAVAILABLE: bench JSON malformed or" \
                "missing — a crashed measurement, not a perf verdict)" ;;
    esac
else
    echo "skipped: tunnel dead"
fi

echo "== 2d. bench --pipeline v4 (whole-chunk megakernel, 60 s) =="
# The v4 wall-clock verdict: the front megakernel (masks + POR +
# compact + delta fingerprints in ONE Mosaic launch) + the fused tail
# against BOTH the v2 XLA chunk (stage 2) and the v3 split-fused chunk
# (stage 2c).  The CI launch pin already proves v4 retires >75% of
# v2's device ops statically; this stage is where that has to cash out
# as states/s on real hardware.  Same degradation story as 2c: a
# Mosaic failure on any stage falls back per plan (fused_stages in the
# JSON names what actually ran), so a partial lowering measures as
# itself instead of wedging the session.
if probe; then
    BENCH_SECONDS=60 BENCH_PIPELINE=v4 BENCH_ORACLE_SECONDS=1 \
        timeout 900 python bench.py \
        2> artifacts/bench_tpu_v4.log | tee artifacts/bench_tpu_v4.json \
        || echo "bench v4 stage failed (rc=$?)"
    python scripts/bench_diff.py artifacts/bench_tpu.json \
        artifacts/bench_tpu_v4.json \
        | tee artifacts/bench_tpu_v2_vs_v4.txt
    case $? in
        0) echo "(v4 holds or beats v2 on this hardware)" ;;
        1) echo "(v4 regressed vs v2 on this hardware — see diff above)" ;;
        *) echo "(v2-vs-v4 diff UNAVAILABLE: bench JSON malformed or" \
                "missing — a crashed measurement, not a perf verdict)" ;;
    esac
    python scripts/bench_diff.py artifacts/bench_tpu_v3.json \
        artifacts/bench_tpu_v4.json \
        | tee artifacts/bench_tpu_v3_vs_v4.txt
    case $? in
        0) echo "(v4 holds or beats v3 on this hardware)" ;;
        1) echo "(v4 regressed vs v3 — the megakernel loses to the" \
                "split-fused chunk here; see diff above)" ;;
        *) echo "(v3-vs-v4 diff UNAVAILABLE: bench JSON malformed or" \
                "missing)" ;;
    esac
else
    echo "skipped: tunnel dead"
fi

echo "== 2e. bench --mode swarm (hunt observatory, 60 s) =="
# The second product tier's driver metric: swarm steps/s on real
# hardware, with the perf accounting (launches/chunk) and the hunt
# summary (saturation, novelty trajectory, time-to-violation) embedded
# in the JSON — bench_diff gates later swarm rounds on BOTH rate and
# hunt drift (--hunt-drift), and bench_history --hunt renders the
# saturation trajectory.  Diffed against the v2 bench only to record
# the cross-dialect fold note (distinct/s vs steps/s are different
# headlines; nothing is gated across modes).
if probe; then
    BENCH_SECONDS=60 BENCH_MODE=swarm BENCH_ORACLE_SECONDS=1 \
        timeout 900 python bench.py \
        2> artifacts/bench_tpu_swarm.log \
        | tee artifacts/bench_tpu_swarm.json \
        || echo "bench swarm stage failed (rc=$?)"
    python scripts/bench_diff.py artifacts/bench_tpu.json \
        artifacts/bench_tpu_swarm.json \
        | tee artifacts/bench_tpu_v2_vs_swarm.txt \
        || echo "(cross-mode diff rc=$? — expected note-only fold)"
else
    echo "skipped: tunnel dead"
fi

echo "== 3. leader-rich bench (60 s) =="
if probe; then
    timeout 900 python scripts/leader_bench.py 60 \
        2> artifacts/leader_bench_tpu.log \
        | tee artifacts/leader_bench_tpu.json \
        || echo "leader bench failed (rc=$?)"
else
    echo "skipped: tunnel dead"
fi

echo "== 4. profile_step (B=2048, then B=8192 batch-scaling probe) =="
if probe; then
    timeout 1200 python scripts/profile_step.py 2048 \
        2> artifacts/profile_step_tpu.log \
        | tee artifacts/profile_step_tpu.txt \
        || echo "profile stage failed (rc=$?)"
    timeout 1200 python scripts/profile_step.py 8192 \
        2> artifacts/profile_step_tpu_b8192.log \
        | tee artifacts/profile_step_tpu_b8192.txt \
        || echo "profile b8192 failed (rc=$?)"
else
    echo "skipped: tunnel dead"
fi

echo "== 5. north-star attempt (budget ${NS_BUDGET}s, ckpt+spill) =="
if probe; then
    # --metrics-port + the background watch console give the long run a
    # live view; a mid-run death leaves artifacts/ns_ckpt/postmortem.json
    # (flight recorder) with the last progress snapshots.
    start_watch northstar_watch.log
    timeout $((NS_BUDGET + 600)) python -m raft_tla_tpu check \
        configs/TPUraft.cfg ${PLAT_ARGS} --max-seconds "${NS_BUDGET}" \
        --no-trace --metrics-port "${METRICS_PORT}" \
        --checkpoint-dir artifacts/ns_ckpt --spill-dir artifacts/ns_spill \
        2> artifacts/northstar_tpu.log | tee artifacts/northstar_tpu.txt \
        || echo "north-star stage failed (rc=$?)"
    stop_watch
else
    echo "skipped: tunnel dead"
fi

echo "== 5b. device-profiler capture (--xla-profile, v2/v3/v4) =="
# The NORTHSTAR §d hardware verdict needs to see INSIDE the chunk
# program (kernel launches, HBM traffic) — jax.profiler artifacts
# (XPlane + Perfetto trace), correlated with the host spans by the
# shared "chunk" span name.  Short budgets: the capture window is the
# first 16 chunk calls; even a session cut right after this stage has
# the hardware profile for both pipelines.
if probe; then
    for pipe in v2 v3 v4; do
        timeout 600 python -m raft_tla_tpu check \
            configs/MCraft_bounded.cfg ${PLAT_ARGS} --max-seconds 60 \
            --no-trace --pipeline "$pipe" --xla-profile 16 \
            --xla-profile-dir "artifacts/xla_profile_${pipe}" \
            2> "artifacts/xla_profile_${pipe}.log" \
            | tee "artifacts/xla_profile_${pipe}.txt" \
            || echo "xla-profile ${pipe} stage failed (rc=$?)"
    done
    ls -R artifacts/xla_profile_v2 artifacts/xla_profile_v3 2>/dev/null \
        | head -20
    # XPlane ingestion (scripts/xplane_summary.py): fold each capture's
    # Perfetto trace into the perf JSON dialect and into the run ledger
    # (kind=xplane), so the measured launches/chunk lands next to the
    # bench trajectory instead of staying a profiler screenshot —
    # bench_diff --launch-drift can then gate v2-vs-v3 on MEASURED
    # launch counts.
    for pipe in v2 v3 v4; do
        python scripts/xplane_summary.py "artifacts/xla_profile_${pipe}" \
            --out "artifacts/xplane_summary_${pipe}.json" \
            --history artifacts/history.jsonl \
            --label "xplane_${pipe}" \
            || echo "xplane summary ${pipe} failed (rc=$?)"
    done
    python scripts/bench_diff.py artifacts/xplane_summary_v2.json \
        artifacts/xplane_summary_v3.json \
        | tee artifacts/xplane_v2_vs_v3.txt \
        || echo "xplane v2-vs-v3 launch diff: rc=$? (1 = launch "\
"regression verdict, 2 = unreadable capture)"
    python scripts/bench_diff.py artifacts/xplane_summary_v2.json \
        artifacts/xplane_summary_v4.json \
        | tee artifacts/xplane_v2_vs_v4.txt \
        || echo "xplane v2-vs-v4 launch diff: rc=$? (1 = launch "\
"regression verdict, 2 = unreadable capture)"
    # Swarm walk-chunk capture: the same device-truth treatment for the
    # second tier — the scan-step launch pin (tests/test_perf.py
    # SWARM_LAUNCH_PINS) is a jaxpr count; this is where it gets
    # checked against what the hardware actually scheduled.
    timeout 600 python -m raft_tla_tpu check \
        configs/MCraft_bounded.cfg ${PLAT_ARGS} --mode swarm \
        --walks 1024 --max-depth 16 --max-seconds 60 \
        --xla-profile 16 \
        --xla-profile-dir artifacts/xla_profile_swarm \
        2> artifacts/xla_profile_swarm.log \
        | tee artifacts/xla_profile_swarm.txt \
        || echo "xla-profile swarm stage failed (rc=$?)"
    python scripts/xplane_summary.py artifacts/xla_profile_swarm \
        --out artifacts/xplane_summary_swarm.json \
        --history artifacts/history.jsonl \
        --label "xplane_swarm" \
        || echo "xplane summary swarm failed (rc=$?)"
else
    echo "skipped: tunnel dead"
fi

echo "== 6. simulation at scale (300 s cap) =="
if probe; then
    timeout 600 python -m raft_tla_tpu simulate configs/MCraft_bounded.cfg \
        ${PLAT_ARGS} --batch 8192 --num-steps 134217728 --max-seconds 300 \
        2> artifacts/simulate_tpu.log | tee artifacts/simulate_tpu.txt \
        || echo "simulate stage failed (rc=$?)"
else
    echo "skipped: tunnel dead"
fi

echo "== session complete; artifacts/ =="
ls -la artifacts/
# Exit 0 only if the headline stage produced a REAL accelerator artifact —
# bench.py falls back to CPU (and still emits JSON) when the tunnel dies
# mid-session, and the watchdog must keep probing in that case, not
# declare victory on a CPU number.
if [ "${RAFT_SESSION_ALLOW_CPU:-0}" = "1" ]; then
    [ -s artifacts/bench_tpu.json ]
else
    [ -s artifacts/bench_tpu.json ] \
        && ! grep -q '"platform": "cpu"' artifacts/bench_tpu.json
fi
