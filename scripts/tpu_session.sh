#!/bin/bash
# One-shot TPU measurement session (round-3 performance evidence).
# Run when the TPU tunnel is alive; everything lands in artifacts/.
#
#   bash scripts/tpu_session.sh [budget_seconds_for_northstar]
#
# Stages (each skipped gracefully if a prior one shows the tunnel dead):
#   1. probe           - fail fast if the tunnel is wedged
#   2. profile_step    - per-stage device timings (the round-3 instrument)
#   3. bench           - the driver metric (BENCH_SECONDS=60)
#   4. north star      - raft5/TPUraft.cfg on one chip, checkpoint+spill,
#                        budgeted; level profile recorded
#   5. simulation      - BASELINE configs[3] scale (capped by time budget)
set -u
set -o pipefail   # a crashed stage must not be masked by tee
cd "$(dirname "$0")/.."
mkdir -p artifacts
NS_BUDGET="${1:-900}"

probe() {
    # RAFT_SESSION_ALLOW_CPU=1 lets the whole pipeline be smoke-tested
    # without an accelerator (stages then run on the CPU fallback).
    if [ "${RAFT_SESSION_ALLOW_CPU:-0}" = "1" ]; then
        return 0
    fi
    timeout 180 python -c \
        "import jax; assert jax.devices()[0].platform != 'cpu'" \
        2>/dev/null
}

# CLI stages need an explicit platform in CPU-smoke mode — the ambient
# backend is the (possibly dead) tunnel regardless of JAX_PLATFORMS.
PLAT_ARGS=""
if [ "${RAFT_SESSION_ALLOW_CPU:-0}" = "1" ]; then
    PLAT_ARGS="--platform cpu"
fi

echo "== 1. probe =="
if ! probe; then
    echo "TPU tunnel unavailable; aborting session."
    exit 1
fi
echo "tpu ok"

# Single-core host: a background CPU measurement (e.g. the configs[3]
# simulation sweep) would starve XLA compilation for every stage below —
# the TPU session takes priority the moment the tunnel answers.
pkill -f "raft_tla_tpu simulate.*platform cpu" 2>/dev/null && \
    echo "(killed background CPU simulation sweep; TPU session takes priority)"

echo "== 2. profile_step (B=2048) =="
timeout 1200 python scripts/profile_step.py 2048 \
    2> artifacts/profile_step_tpu.log | tee artifacts/profile_step_tpu.txt

echo "== 3. bench (60 s budget) =="
# stdout only into the .json — bench prints exactly one JSON line there;
# stderr (fallback notices, absl logs) goes to the .log.
probe || { echo "tunnel died before bench; stopping"; exit 1; }
BENCH_SECONDS=60 timeout 900 python bench.py \
    2> artifacts/bench_tpu.log | tee artifacts/bench_tpu.json

echo "== 3b. leader-rich bench (60 s) =="
probe || { echo "tunnel died before leader bench; stopping"; exit 1; }
timeout 900 python scripts/leader_bench.py 60 \
    2> artifacts/leader_bench_tpu.log | tee artifacts/leader_bench_tpu.json

echo "== 4. north-star attempt (budget ${NS_BUDGET}s, ckpt+spill) =="
probe || { echo "tunnel died before north star; stopping"; exit 1; }
timeout $((NS_BUDGET + 600)) python -m raft_tla_tpu check \
    configs/TPUraft.cfg ${PLAT_ARGS} --max-seconds "${NS_BUDGET}" --no-trace \
    --checkpoint-dir artifacts/ns_ckpt --spill-dir artifacts/ns_spill \
    2> artifacts/northstar_tpu.log | tee artifacts/northstar_tpu.txt

echo "== 5. simulation at scale (300 s cap) =="
probe || { echo "tunnel died before simulate; stopping"; exit 1; }
timeout 600 python -m raft_tla_tpu simulate configs/MCraft_bounded.cfg \
    ${PLAT_ARGS} --batch 8192 --num-steps 134217728 --max-seconds 300 \
    2> artifacts/simulate_tpu.log | tee artifacts/simulate_tpu.txt

echo "== session complete; artifacts/ =="
ls -la artifacts/
